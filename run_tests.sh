#!/bin/bash
# Test runner: forces the virtual 8-device CPU platform and — critically —
# skips the axon TPU claim (sitecustomize registers/claims the single TPU at
# EVERY interpreter start when PALLAS_AXON_POOL_IPS is set; concurrent
# claims deadlock and CPU tests don't need the chip at all).
#
# After the unit suite, the telemetry smoke test runs a tiny train loop with
# telemetry enabled and validates every emitted JSONL step record against
# the schema (scripts/telemetry_smoke.py exits nonzero on violation).
# dslint gate (docs/static_analysis.md): the AST invariant checker must
# report ZERO unsuppressed, un-baselined findings on the package —
# host-sync/trace-hygiene in traced code, recompile hazards, lock
# discipline (region -> cell -> fleet -> replica, nothing blocking
# under a held lock), exception discipline, and the dsrace lockset
# races rule (shared attributes reachable from >= 2 thread roles with
# no common lock). It prints its own findings-count summary line.
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python -m deepspeed_tpu.analysis --check --baseline dslint_baseline.json
dslint_rc=$?

# -m "not slow" matches the tier-1 lane (ROADMAP.md): the slow-marked
# autotuner grid searches would otherwise add minutes per run
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest "${@:-tests/}" -q -m "not slow"
pytest_rc=$?

smoke_rc=0
if [ "$#" -eq 0 ]; then
    # full-suite runs only: a targeted ./run_tests.sh tests/test_x.py
    # shouldn't pay the smoke loops' engine builds
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        XLA_FLAGS="--xla_force_host_platform_device_count=8" \
        python scripts/telemetry_smoke.py
    smoke_rc=$?

    # chaos smoke: seeded kill mid-train + ElasticAgent auto-resume; the
    # final loss must be bit-identical to an uninterrupted run
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        XLA_FLAGS="--xla_force_host_platform_device_count=8" \
        python scripts/chaos_smoke.py
    chaos_rc=$?
    if [ "$smoke_rc" -eq 0 ]; then
        smoke_rc=$chaos_rc
    fi

    # DST soak (CPU evidence lane, docs/dst.md): >= 200 seeded
    # randomized fault schedules through the real serving fleet on
    # virtual time — zero invariant violations (block balance, request
    # state machine, no-lost-request conservation, span/SLO ledger,
    # stream delivery, monotone time), and a replay sample must produce
    # bit-identical event-trace hashes. Failures are auto-shrunk to
    # minimal repro JSONs.
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        python scripts/dst_soak.py
    dst_rc=$?
    if [ "$smoke_rc" -eq 0 ]; then
        smoke_rc=$dst_rc
    fi

    # dsrace cross-validation lane (docs/static_analysis.md "races"):
    # fleet + region DST schedules re-run with the runtime lock-order
    # sanitizer installed. Gates: zero sanitizer violations (order
    # inversions / cycles / same-tier nesting), every runtime-observed
    # lock edge present in dslint's STATIC lock graph (a miss is a
    # static-model false negative), every documented-tier static edge
    # exercised, sanitized replays bit-identical, and the dslint races
    # rule repo-clean. Writes RACE_r01.json.
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        python scripts/race_lane.py
    race_rc=$?
    if [ "$smoke_rc" -eq 0 ]; then
        smoke_rc=$race_rc
    fi

    # dslint findings-count trend artifact (DSLINT_TREND.json, fixed
    # name): per-rule live/suppressed/baselined counts so suppression
    # and baseline growth show up as a reviewable diff per PR
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        python scripts/dslint_trend.py
    trend_rc=$?
    if [ "$smoke_rc" -eq 0 ]; then
        smoke_rc=$trend_rc
    fi

    # region soak (CPU evidence lane, docs/serving.md "Region & cells",
    # docs/dst.md "Region-scale events"): >= 200 seeded REGION chaos
    # schedules — whole-cell outages, inter-cell partitions + heals,
    # autoscaler lag, plus every fleet-tier fault — through the real
    # two-tier serving stack on virtual time. Gates: zero invariant
    # violations (incl. heal convergence / single ownership and
    # shed-span), bit-identical (trace_hash, span_hash) replay, every
    # fault kind exercised, brownout shedding strictly priority-ordered.
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        python scripts/region_soak.py
    region_rc=$?
    if [ "$smoke_rc" -eq 0 ]; then
        smoke_rc=$region_rc
    fi

    # gray-failure lane (CPU evidence lane, docs/fault_tolerance.md
    # "Gray failures", docs/dst.md): the scripted straggler experiment
    # (one replica degraded k-fold on virtual time) must quarantine the
    # straggler within the vtick budget, fire hedged backup legs, and
    # beat the plane-off p99 TTFT by the gated ratio without losing
    # work; plus >= 200 seeded gray-chaos schedules (degraded_tick /
    # stall_burst / flaky_import draws) with zero invariant violations
    # — hedge conservation, quarantine convergence + capacity floor,
    # and no-flap included — and bit-identical sampled replays.
    # Writes GRAY_r01.json.
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        python scripts/gray_lane.py
    gray_rc=$?
    if [ "$smoke_rc" -eq 0 ]; then
        smoke_rc=$gray_rc
    fi

    # global KV tier lane (CPU evidence lane, docs/serving.md "Global
    # KV tier", docs/dst.md): the scripted shared-prefix A/B (global
    # tier ON vs per-replica caching only, virtual time) must beat the
    # baseline's global prefix hit rate and mean TTFT by the gated
    # ratios with zero KV page leaks on BOTH legs; plus >= 200 seeded
    # kv-chaos schedules (stale_directory / corrupt_adopt /
    # cold_pressure draws) with zero invariant violations — directory-
    # residency containment, cold-tier accounting, and verify-before-
    # import included — and bit-identical sampled replays.
    # Writes KVTIER_r01.json.
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        python scripts/kvtier_lane.py
    kvtier_rc=$?
    if [ "$smoke_rc" -eq 0 ]; then
        smoke_rc=$kvtier_rc
    fi

    # SLO lane (CPU evidence lane, docs/observability.md "Region
    # rollups & SLO alerting"): >= 200 seeded region chaos schedules
    # with every digest observation mirrored into a pooled ground-truth
    # stream. Gates: merged region sketch sample counts exactly equal
    # pooled counts (outages/partitions/salvage included), p50/p99
    # within the sketch's documented relative-error bound, digest +
    # alert streams bit-identical on replay, rollup cost independent of
    # replica count, and the scripted two-tenant burst fires/clears
    # per-tenant burn-rate alerts deterministically. Writes SLO_r01.json.
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        python scripts/slo_lane.py
    slo_rc=$?
    if [ "$smoke_rc" -eq 0 ]; then
        smoke_rc=$slo_rc
    fi

    # rollout smoke (CPU evidence lane, docs/serving.md "Rollout,
    # canary, and migration"): a scripted end-to-end canary -> promote
    # rollout with a live migration riding along, plus the seeded
    # versioned-serving chaos sweep (rollout / migrate / canary SLO
    # regression / corrupt swap / death-at-flip). Gates: zero invariant
    # violations (incl. version-stream atomicity, per-tenant version
    # monotonicity, rollback convergence), zero lost requests, the
    # availability dip vs a fault-free baseline bounded, bit-identical
    # replay. Writes ROLLOUT_r01.json.
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        python scripts/rollout_smoke.py
    rollout_rc=$?
    if [ "$smoke_rc" -eq 0 ]; then
        smoke_rc=$rollout_rc
    fi

    # serving-scheduler smoke (CPU evidence lane, docs/serving.md): on
    # VIRTUAL time (SimClock; deterministic, no calibration or jitter
    # bands) the SLO-aware policy must serve every offered request
    # in-SLA while FCFS head-of-line blocking misses every interactive
    # deadline, and allocator block balance must be exactly zero after
    # drain() on every leg — including injected tick faults and
    # mid-stream cancellations
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        python scripts/serving_smoke.py
    serve_rc=$?
    if [ "$smoke_rc" -eq 0 ]; then
        smoke_rc=$serve_rc
    fi

    # speculative-serving + quantized-KV smoke (CPU evidence lane,
    # docs/serving.md "Speculative scheduling" / "KV quantization"): on
    # virtual time, the pinned workload served with speculation ON must
    # emit TOKEN-IDENTICAL greedy streams in strictly fewer engine
    # ticks than with it off (drafts proposed AND accepted); an int8 KV
    # pool at the same byte budget must sustain >= 1.8x the concurrent
    # decode sequences; the quantized export_kv hand-off must book a
    # >= 1.8x wire reduction in the comm ledger and adopt bit-equal;
    # zero leaked KV blocks on every leg
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        python scripts/serve_spec_smoke.py
    spec_rc=$?
    if [ "$smoke_rc" -eq 0 ]; then
        smoke_rc=$spec_rc
    fi

    # serving-fleet smoke (CPU evidence lane, docs/serving.md): in-SLA
    # goodput must scale EXACTLY 2x from 1 -> 2 replicas under the
    # seeded overload on virtual time (one full wave per replica, exact
    # tick-count TTFT gate); prefix-affinity routing must beat
    # least-loaded on prefix-cache hit rate; injected replica death
    # (failover) and the disaggregated prefill->decode handoff must be
    # bit-identical to an uninterrupted single-engine run (real
    # threads); zero leaked KV pages on every replica on every leg
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        python scripts/fleet_smoke.py
    fleet_rc=$?
    if [ "$smoke_rc" -eq 0 ]; then
        smoke_rc=$fleet_rc
    fi

    # host-overhead perf smoke (CPU evidence lane, docs/performance.md):
    # steady-state host overhead with prefetch + train_steps(8) must stay
    # >= 2x lower than the synchronous per-step path, with zero
    # shape-churn recompiles. The bench sizes its own device mesh.
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        python scripts/host_overhead_bench.py --check
    perf_rc=$?
    if [ "$smoke_rc" -eq 0 ]; then
        smoke_rc=$perf_rc
    fi

    # quant-comm gate (CPU evidence lane, docs/communication.md): the
    # compressed-collectives facade must show >= 2x wire-byte reduction
    # on the int8 weight all-gather and >= 4x on the int4 inter-slice
    # gradient hop per the bytes-on-wire ledger, quantization error
    # within the documented QuantSpec bound, the staged T3 overlap
    # schedule bit-exact to serial with compression off, zero recompiles
    # in the overlapped fused scan, and the committed NORTHSTAR
    # projection's overlapped zero3 comm exposure cut >= 50% vs the
    # serial booking. The smoke sizes its own 8-device mesh.
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        python scripts/quant_comm_smoke.py
    qc_rc=$?
    if [ "$smoke_rc" -eq 0 ]; then
        smoke_rc=$qc_rc
    fi

    # fused-kernel gate (CPU evidence lane, docs/communication.md
    # "Kernel backends"): the staged engine on the fused Pallas backend
    # (interpret mode) must be BIT-exact to the XLA backend — losses
    # and parameters, compressed and dense — with fusion engaging and
    # structural fallbacks metered, zero recompiles across fused-scan
    # steps, and the modeled per-tile exposure strictly below the PR-10
    # per-layer block-schedule number
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        python scripts/_comm_lane.py --fused
    fused_rc=$?
    if [ "$smoke_rc" -eq 0 ]; then
        smoke_rc=$fused_rc
    fi

    # trace lane (CPU evidence lane, docs/observability.md "Tracing &
    # flight recorder"): a seeded DST schedule run twice must produce
    # bit-identical canonical span-tree hashes; the Chrome-trace export
    # must pass the schema check; a planted tick-fault with a spent
    # retry budget must auto-dump the flight recorder to disk; and
    # engine.overlap_report()'s MEASURED comm exposure must agree with
    # modeled_exposure within the documented band (TIMELINE_r01.json)
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        XLA_FLAGS="--xla_force_host_platform_device_count=8" \
        python scripts/trace_smoke.py
    trace_rc=$?
    if [ "$smoke_rc" -eq 0 ]; then
        smoke_rc=$trace_rc
    fi
fi

if [ "$dslint_rc" -ne 0 ]; then
    exit "$dslint_rc"
fi
if [ "$pytest_rc" -ne 0 ]; then
    exit "$pytest_rc"
fi
exit "$smoke_rc"
