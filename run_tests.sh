#!/bin/bash
# Test runner: forces the virtual 8-device CPU platform and — critically —
# skips the axon TPU claim (sitecustomize registers/claims the single TPU at
# EVERY interpreter start when PALLAS_AXON_POOL_IPS is set; concurrent
# claims deadlock and CPU tests don't need the chip at all).
exec env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest "${@:-tests/}" -q
