// Async file I/O engine (thread-pool pread/pwrite with a completion queue).
//
// Native-parity component for the reference's csrc/aio/ stack
// (deepspeed_aio_thread.cpp thread pool + py_lib/deepspeed_py_aio_handle.cpp
// `aio_handle` pybind surface). The reference drives libaio against
// O_DIRECT NVMe; this engine uses a portable POSIX thread pool issuing
// pread/pwrite — the same asynchrony contract (submit returns immediately,
// wait() drains completions) on any filesystem, which is what the
// host-RAM <-> SSD offload tier needs. Exposed to Python through ctypes
// (deepspeed_tpu/ops/aio.py), not pybind (no pybind11 in the image).
//
// Build: g++ -O2 -shared -fPIC -pthread ds_aio.cpp -o libds_aio.so

#include <atomic>
#include <cerrno>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <mutex>
#include <string>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Request {
  int64_t id;
  bool is_read;
  std::string path;
  void* buffer;
  int64_t nbytes;
  int64_t offset;
  bool trunc;  // writes only: truncate file to offset+nbytes afterwards
};

struct Completion {
  int64_t id;
  int64_t result;  // bytes transferred or -errno
};

class AioEngine {
 public:
  AioEngine(int n_threads, int queue_depth)
      : queue_depth_(queue_depth), stop_(false), inflight_(0) {
    for (int i = 0; i < n_threads; ++i) {
      workers_.emplace_back([this] { worker_loop(); });
    }
  }

  ~AioEngine() {
    {
      std::unique_lock<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& t : workers_) t.join();
  }

  int64_t submit(bool is_read, const char* path, void* buffer, int64_t nbytes,
                 int64_t offset, bool trunc = false) {
    std::unique_lock<std::mutex> lk(mu_);
    if ((int)pending_.size() >= queue_depth_) return -1;
    int64_t id = next_id_++;
    pending_.push_back(Request{id, is_read, path, buffer, nbytes, offset, trunc});
    ++inflight_;
    cv_.notify_one();
    return id;
  }

  // Blocks until `count` completions are available; fills ids/results.
  int64_t wait(int64_t count, int64_t* ids, int64_t* results) {
    std::unique_lock<std::mutex> lk(mu_);
    done_cv_.wait(lk, [this, count] { return (int64_t)done_.size() >= count; });
    int64_t n = 0;
    while (n < count && !done_.empty()) {
      ids[n] = done_.front().id;
      results[n] = done_.front().result;
      done_.pop_front();
      ++n;
    }
    return n;
  }

  // Non-blocking: number of completions ready.
  int64_t poll() {
    std::unique_lock<std::mutex> lk(mu_);
    return (int64_t)done_.size();
  }

  int64_t inflight() {
    std::unique_lock<std::mutex> lk(mu_);
    return inflight_;
  }

 private:
  void worker_loop() {
    for (;;) {
      Request req;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [this] { return stop_ || !pending_.empty(); });
        if (stop_ && pending_.empty()) return;
        req = pending_.front();
        pending_.pop_front();
      }
      int64_t result = run(req);
      {
        std::unique_lock<std::mutex> lk(mu_);
        done_.push_back(Completion{req.id, result});
        --inflight_;
      }
      done_cv_.notify_all();
    }
  }

  static int64_t run(const Request& req) {
    int flags = req.is_read ? O_RDONLY : (O_WRONLY | O_CREAT);
    int fd = ::open(req.path.c_str(), flags, 0644);
    if (fd < 0) return -errno;
    int64_t total = 0;
    char* buf = static_cast<char*>(req.buffer);
    while (total < req.nbytes) {
      ssize_t n = req.is_read
          ? ::pread(fd, buf + total, req.nbytes - total, req.offset + total)
          : ::pwrite(fd, buf + total, req.nbytes - total, req.offset + total);
      if (n < 0) {
        ::close(fd);
        return -errno;
      }
      if (n == 0) break;  // EOF
      total += n;
    }
    // caller-requested truncation: drop any stale tail beyond this write
    // (an explicit flag, not inferred from offset — inferring from offset==0
    // would race with concurrent chunk writes to other offsets of the file)
    if (!req.is_read && req.trunc) {
      if (::ftruncate(fd, req.offset + total) != 0) {
        int64_t err = -errno;
        ::close(fd);
        return err;
      }
    }
    ::close(fd);
    return total;
  }

  int queue_depth_;
  bool stop_;
  int64_t inflight_;
  int64_t next_id_ = 1;
  std::deque<Request> pending_;
  std::deque<Completion> done_;
  std::vector<std::thread> workers_;
  std::mutex mu_;
  std::condition_variable cv_, done_cv_;
};

}  // namespace

extern "C" {

void* ds_aio_create(int n_threads, int queue_depth) {
  return new AioEngine(n_threads, queue_depth);
}

void ds_aio_destroy(void* h) { delete static_cast<AioEngine*>(h); }

int64_t ds_aio_pread(void* h, const char* path, void* buf, int64_t nbytes,
                     int64_t offset) {
  return static_cast<AioEngine*>(h)->submit(true, path, buf, nbytes, offset);
}

int64_t ds_aio_pwrite(void* h, const char* path, void* buf, int64_t nbytes,
                      int64_t offset) {
  return static_cast<AioEngine*>(h)->submit(false, path, buf, nbytes, offset);
}

// write + truncate-to-end: for whole-file rewrites that must not leave a
// stale tail when the new contents are shorter than the old file
int64_t ds_aio_pwrite_trunc(void* h, const char* path, void* buf,
                            int64_t nbytes, int64_t offset) {
  return static_cast<AioEngine*>(h)->submit(false, path, buf, nbytes, offset,
                                            /*trunc=*/true);
}

int64_t ds_aio_wait(void* h, int64_t count, int64_t* ids, int64_t* results) {
  return static_cast<AioEngine*>(h)->wait(count, ids, results);
}

int64_t ds_aio_poll(void* h) { return static_cast<AioEngine*>(h)->poll(); }

int64_t ds_aio_inflight(void* h) {
  return static_cast<AioEngine*>(h)->inflight();
}

}  // extern "C"
