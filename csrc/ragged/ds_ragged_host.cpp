// Host-side ragged batch construction for the continuous-batching engine.
//
// Parity target: the reference keeps FastGen's batch building native —
// inference/v2/ragged/csrc/fast_host_buffer.cpp builds the flattened
// token/metadata buffers the ragged kernels consume. Here the same role:
// given the scheduled per-sequence chunks (concatenated tokens + offsets)
// fill the flat [T] token/slot/position lanes, and scatter per-sequence
// block lists into the dense [max_seqs, max_pages] table the paged
// attention kernel prefetches.
//
// Plain C ABI for the ctypes registry (ops/op_builder.py); no torch, no
// pybind — see csrc/aio/ds_aio.cpp for the house style.

#include <cstdint>

extern "C" {

// tokens_concat: all scheduled chunks back-to-back; offsets: [n+1] chunk
// boundaries; seens/slots: [n] per scheduled sequence. Fills
// flat_tokens/flat_slot/flat_pos (caller-allocated [T], pre-filled with
// padding) and last_index [n] = flat index of each sequence's final token.
void ds_ragged_build_batch(int32_t n,
                           const int32_t* tokens_concat,
                           const int32_t* offsets,
                           const int32_t* seens,
                           const int32_t* slots,
                           int32_t* flat_tokens,
                           int32_t* flat_slot,
                           int32_t* flat_pos,
                           int32_t* last_index) {
  int32_t cursor = 0;
  for (int32_t i = 0; i < n; ++i) {
    const int32_t take = offsets[i + 1] - offsets[i];
    const int32_t* chunk = tokens_concat + offsets[i];
    const int32_t seen = seens[i];
    const int32_t slot = slots[i];
    for (int32_t j = 0; j < take; ++j) {
      flat_tokens[cursor + j] = chunk[j];
      flat_slot[cursor + j] = slot;
      flat_pos[cursor + j] = seen + j;
    }
    cursor += take;
    last_index[i] = cursor - 1;
  }
}

// blocks_concat: every live sequence's block list back-to-back; offsets:
// [n+1]; slots: [n]. Scatters into tables [max_seqs * max_pages]
// (caller-zeroed), row-major by slot. Returns the number of sequences
// whose block list exceeded max_pages — such rows are written only up to
// max_pages (no OOB), and a non-zero return is an engine invariant
// violation the wrapper raises on (never silently truncate into wrong
// attention reads).
int32_t ds_ragged_fill_tables(int32_t n,
                              const int32_t* blocks_concat,
                              const int32_t* offsets,
                              const int32_t* slots,
                              int32_t max_pages,
                              int32_t* tables) {
  int32_t overflowed = 0;
  for (int32_t i = 0; i < n; ++i) {
    const int32_t count = offsets[i + 1] - offsets[i];
    if (count > max_pages) ++overflowed;
    const int32_t* blocks = blocks_concat + offsets[i];
    int32_t* row = tables + static_cast<int64_t>(slots[i]) * max_pages;
    const int32_t lim = count < max_pages ? count : max_pages;
    for (int32_t j = 0; j < lim; ++j) {
      row[j] = blocks[j];
    }
  }
  return overflowed;
}

}  // extern "C"
